"""Fleet-wide request journeys — cross-replica hop correlation.

PRs 15-18 spread one request's life across many components: router
placement, failover re-enqueue, disaggregated prefill/decode
hand-off, hierarchical KV offload, streaming delivery.  Observability
stayed per-server — each replica has its own ``SpanTracer``,
``FlightRecorder`` and ``stats()`` — so the operator question *"why
was THIS request slow?"* needed a manual join across artifacts.  This
module is that join, made first-class:

- :class:`JourneyContext` — the correlation token.  One per request,
  created at the fleet front door (journey id = the router ``rid``)
  or at a bare server's ``submit`` (journey id = the request ``uid``),
  carried by the ``RouterRequest`` across failover and hand-off and by
  ``Request.journey`` inside each server.  It holds the id plus a hop
  counter: every recorded hop draws the next sequence number from the
  context, so the hop order is CAUSAL BY CONSTRUCTION — the counter
  travels with the request, and two hops can never race it because a
  request is only ever live on one replica at a time (the router's
  exactly-once terminal invariant).

- :class:`JourneyLog` — the per-replica recording plane.  Each server
  (and the router itself) owns one, labeled with its replica name and
  wired to the owner's injected iteration counter and clock — hops
  carry ``(replica, iter, seq, t)`` with NO wall-clock reads of their
  own, so journeys are byte-deterministic wherever the soak clocks
  are.  Recording never draws randomness and never feeds back into
  scheduling: seed-0 chaos schedules are byte-identical journeys-on.

- :class:`NullJourneyLog` / :data:`NULL_JOURNEY_LOG` — the disabled
  path, mirroring ``NULL_TRACER`` / ``NULL_FLIGHT_RECORDER``: every
  stamping site guards on ``journeys.enabled`` (and request contexts
  stay ``None``), so a server built without journeys allocates
  NOTHING per token (``tests/L0/test_journey.py`` pins it with
  tracemalloc).

- :func:`merge_journeys` — the reconciliation: per-replica hop
  records merged into one causally-ordered :class:`Journey` per rid.
  The merge sorts by the context-issued ``seq`` alone — equivalent to
  the (replica-visit, iter, hop-seq) order but needing no clock
  comparison across replicas — so a journey whose request moved
  replicas mid-stream (failover) or mid-hand-off (torn transfer
  retried) still reads front-to-back, exactly once.  A COMPLETE
  journey has exactly one ``finish`` hop and a contiguous ``1..N``
  sequence — the property ``tools/journey.py --assert-complete``
  gates and the chaos soaks assert per finished rid.

- SLO exemplars: :meth:`JourneyLog.exemplar` keeps, per histogram
  bucket of a metric (TTFT / ITL), the WORST observation's value and
  rid — so an SLO-miss p99 bucket links directly to a renderable
  journey instead of a number with no story.

Surfaces: ``stats()["journeys"]`` (pinned census), ``journey(rid)``
on both ``InferenceServer`` and ``RouterFleet``, the ops plane's
``GET /debug/journey/<rid>``, the postmortem bundle's
``journeys.json`` member, and ``tools/journey.py`` (``--rid``,
``--slowest``, ``--assert-complete``).  See ``docs/observability.md``,
"Request journeys & exemplars".
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional

__all__ = [
    "HOP_FINISH",
    "JOURNEYS_ENV",
    "Journey",
    "JourneyContext",
    "JourneyLog",
    "NULL_JOURNEY_LOG",
    "NullJourneyLog",
    "dump_journeys",
    "journeys_census",
    "merge_exemplars",
    "merge_journeys",
    "resolve_journeys",
]

# the terminal hop kind — exactly one per complete journey
HOP_FINISH = "finish"

# env twin of ``enable_journeys=`` (the KV_OFFLOAD_ENV pattern): turns
# the journey plane on fleet-wide without touching call sites; a
# PROVIDED kwarg wins
JOURNEYS_ENV = "APEX_TPU_JOURNEYS"


def resolve_journeys(value) -> bool:
    """Normalize an ``enable_journeys`` kwarg/env value to a bool.
    ``None`` / ``""`` / ``"0"`` / ``"off"`` / ``"none"`` / ``"false"``
    / ``"no"`` disable; ``"1"`` / ``"on"`` / ``"true"`` / ``"yes"``
    enable; anything else raises — a typo'd env var must not silently
    run the fleet without its correlation plane."""
    if value is None:
        return False
    if isinstance(value, bool):
        return value
    v = str(value).strip().lower()
    if v in ("", "0", "off", "none", "false", "no"):
        return False
    if v in ("1", "on", "true", "yes"):
        return True
    raise ValueError(
        f"unrecognized enable_journeys / {JOURNEYS_ENV} value: "
        f"{value!r}")

# pinned census shape (``stats()["journeys"]``): present and
# shape-stable whether the plane is enabled or not, like the
# ``flight`` / ``offload`` blocks (``tests/L0/test_journey.py``)
_CENSUS_KEYS = ("enabled", "started", "finished", "open", "hops",
                "dropped", "exemplars")


class JourneyContext:
    """The correlation token carried by one request: a stable journey
    id (router ``rid``, or ``uid`` on a bare server) plus the hop
    counter every recording site draws from.  Tiny and slotted — one
    lives on every in-flight request while journeys are enabled."""

    __slots__ = ("rid", "seq")

    def __init__(self, rid: int):
        self.rid = int(rid)
        self.seq = 0

    def next_hop(self) -> int:
        self.seq += 1
        return self.seq

    def __repr__(self) -> str:
        return f"JourneyContext(rid={self.rid}, seq={self.seq})"


class JourneyLog:
    """One replica's journey hop store.

    Args:
      replica: the label stamped on every hop this log records —
        ``"router"`` at the fleet front door, the replica name inside
        each server.
      iter_source: zero-arg callable returning the owner's current
        iteration (the server/fleet ``_iter``); hops are ordered on
        these injected counters, never on wall clocks.
      clock: the owner's injected seconds source — used only for
        rendering/latency math, never for ordering.
      capacity: bound on distinct rids retained; the OLDEST journey
        is dropped past it (``dropped`` counts them).  Recording is
        observation-only: no randomness, no feedback into scheduling.
    """

    enabled = True

    def __init__(self, *, replica: str = "server",
                 iter_source: Optional[Callable[[], int]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.replica = replica
        self.capacity = capacity
        self._iter_source = iter_source or (lambda: 0)
        self._clock = clock or (lambda: 0.0)
        self._hops: Dict[int, List[dict]] = {}   # rid -> hop records
        self._order: List[int] = []              # rid insertion order
        self.started = 0
        self.finished = 0
        self.hops_recorded = 0
        self.dropped = 0
        # metric -> {bucket_index: (value, rid)} — worst value wins
        self._exemplars: Dict[str, Dict[int, tuple]] = {}

    # -- recording ---------------------------------------------------------

    def start(self, rid: int) -> JourneyContext:
        """Open a journey and return its traveling context."""
        self.started += 1
        return JourneyContext(rid)

    def hop(self, ctx: JourneyContext, kind: str, **detail) -> None:
        """Record one hop for ``ctx``'s journey: the context issues
        the sequence number, this log stamps its replica label and the
        injected iteration/clock.  ``kind == "finish"`` closes the
        journey (census ``finished``)."""
        rec = {"rid": ctx.rid, "seq": ctx.next_hop(),
               "replica": self.replica,
               "iter": int(self._iter_source()),
               "t": float(self._clock()), "kind": kind}
        if detail:
            rec.update(detail)
        bucket = self._hops.get(ctx.rid)
        if bucket is None:
            bucket = self._hops[ctx.rid] = []
            self._order.append(ctx.rid)
            while len(self._order) > self.capacity:
                victim = self._order.pop(0)
                self._hops.pop(victim, None)
                self.dropped += 1
        bucket.append(rec)
        self.hops_recorded += 1
        if kind == HOP_FINISH:
            self.finished += 1

    def exemplar(self, metric: str, bucket: int, value: float,
                 rid: int) -> None:
        """Keep the worst (largest) observation per histogram bucket
        of ``metric``, with the rid that produced it — the SLO-miss ->
        journey link."""
        slots = self._exemplars.setdefault(metric, {})
        cur = slots.get(bucket)
        if cur is None or value > cur[0]:
            slots[bucket] = (float(value), int(rid))

    # -- reads -------------------------------------------------------------

    def hops_for(self, rid: int) -> List[dict]:
        return list(self._hops.get(rid, ()))

    def rids(self) -> List[int]:
        return list(self._order)

    def exemplars(self) -> Dict[str, Dict[str, dict]]:
        """JSON-shaped exemplar view: metric -> bucket-index (str) ->
        ``{"value", "rid"}``."""
        return {metric: {str(b): {"value": v, "rid": rid}
                         for b, (v, rid) in sorted(slots.items())}
                for metric, slots in sorted(self._exemplars.items())}

    def census(self) -> dict:
        return {"enabled": True, "started": self.started,
                "finished": self.finished,
                "open": max(0, self.started - self.finished),
                "hops": self.hops_recorded, "dropped": self.dropped,
                "exemplars": self.exemplars()}

    def clear(self) -> None:
        self._hops.clear()
        self._order.clear()
        self._exemplars.clear()
        self.started = self.finished = 0
        self.hops_recorded = self.dropped = 0


class NullJourneyLog:
    """Journeys OFF: the zero-allocation stand-in (``NULL_TRACER`` /
    ``NullFlightRecorder`` precedent).  Every method is a no-op;
    ``start`` returns None so requests carry no context and every
    per-hop site short-circuits on ``enabled`` / ``ctx is None``."""

    enabled = False
    replica = "null"
    started = 0
    finished = 0
    hops_recorded = 0
    dropped = 0

    def start(self, rid: int) -> None:
        return None

    def hop(self, ctx, kind: str, **detail) -> None:
        pass

    def exemplar(self, metric: str, bucket: int, value: float,
                 rid: int) -> None:
        pass

    def hops_for(self, rid: int) -> List[dict]:
        return []

    def rids(self) -> List[int]:
        return []

    def exemplars(self) -> dict:
        return {}

    def census(self) -> dict:
        return {"enabled": False, "started": 0, "finished": 0,
                "open": 0, "hops": 0, "dropped": 0,
                "exemplars": {}}

    def clear(self) -> None:
        pass


NULL_JOURNEY_LOG = NullJourneyLog()


class Journey:
    """One request's merged, causally-ordered hop sequence."""

    __slots__ = ("rid", "hops")

    def __init__(self, rid: int, hops: List[dict]):
        self.rid = rid
        # the ordering argument (docs/observability.md): ``seq`` is
        # issued by the ONE context object that travels with the
        # request, so sorting on it alone is the (replica-visit,
        # iter, hop-seq) causal order with no cross-replica clock
        # comparison — wall clocks never participate
        self.hops = sorted(hops, key=lambda h: h["seq"])

    @property
    def complete(self) -> bool:
        """Exactly one terminal hop AND a gap-free ``1..N`` sequence —
        the exactly-once reconciliation the chaos soaks assert."""
        seqs = [h["seq"] for h in self.hops]
        return (sum(h["kind"] == HOP_FINISH for h in self.hops) == 1
                and seqs == list(range(1, len(seqs) + 1)))

    @property
    def finish_reason(self) -> Optional[str]:
        for h in reversed(self.hops):
            if h["kind"] == HOP_FINISH:
                return h.get("reason")
        return None

    @property
    def replicas(self) -> List[str]:
        """Replicas visited, in first-touch order."""
        seen: List[str] = []
        for h in self.hops:
            if h["replica"] not in seen:
                seen.append(h["replica"])
        return seen

    def duration(self) -> float:
        """Last-hop minus first-hop time on the injected clocks (0.0
        for an empty/single-hop journey)."""
        if len(self.hops) < 2:
            return 0.0
        return self.hops[-1]["t"] - self.hops[0]["t"]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for h in self.hops:
            out[h["kind"]] = out.get(h["kind"], 0) + 1
        return out

    def as_dict(self) -> dict:
        return {"rid": self.rid, "complete": self.complete,
                "finish_reason": self.finish_reason,
                "replicas": self.replicas,
                "duration": self.duration(),
                "hop_counts": self.counts(), "hops": list(self.hops)}


def merge_journeys(logs: Iterable, *,
                   rid: Optional[int] = None) -> Dict[int, Journey]:
    """Merge per-replica :class:`JourneyLog`\\ s into ``rid ->
    Journey`` (or just one rid's when ``rid`` is given).  Disabled /
    null logs contribute nothing.  Deterministic under any clock
    values: ordering rides the context-issued sequence numbers."""
    pools: Dict[int, List[dict]] = {}
    for log in logs:
        if not getattr(log, "enabled", False):
            continue
        targets = [rid] if rid is not None else log.rids()
        for r in targets:
            hops = log.hops_for(r)
            if hops:
                pools.setdefault(r, []).extend(hops)
    return {r: Journey(r, hops) for r, hops in sorted(pools.items())}


def merge_exemplars(logs: Iterable) -> Dict[str, Dict[str, dict]]:
    """Worst-per-bucket union of per-replica exemplar tables."""
    out: Dict[str, Dict[str, dict]] = {}
    for log in logs:
        if not getattr(log, "enabled", False):
            continue
        for metric, slots in log.exemplars().items():
            mine = out.setdefault(metric, {})
            for b, obs in slots.items():
                cur = mine.get(b)
                if cur is None or obs["value"] > cur["value"]:
                    mine[b] = dict(obs)
    return out


def journeys_census(logs: Iterable) -> dict:
    """Aggregate census over per-replica logs — the fleet-level
    ``stats()["journeys"]`` block.  Shape-stable with the single-log
    census (same pinned keys); all-disabled collapses to the null
    census."""
    logs = [log for log in logs if getattr(log, "enabled", False)]
    if not logs:
        return NullJourneyLog().census()
    started = sum(log.started for log in logs)
    finished = sum(log.finished for log in logs)
    return {"enabled": True, "started": started, "finished": finished,
            "open": max(0, started - finished),
            "hops": sum(log.hops_recorded for log in logs),
            "dropped": sum(log.dropped for log in logs),
            "exemplars": merge_exemplars(logs)}


def dump_journeys(logs: Iterable) -> dict:
    """The postmortem-bundle member (``journeys.json``): every merged
    journey (as dicts) plus the aggregate census — what
    ``tools/journey.py`` renders and gates offline."""
    logs = list(logs)
    merged = merge_journeys(logs)
    return {"census": journeys_census(logs),
            "journeys": {str(r): j.as_dict()
                         for r, j in merged.items()}}
