"""SLO attainment and goodput accounting for the serving stack.

Throughput counts every token; an operator serving millions of users
cares about **goodput** — tokens delivered *within* the latency
contract of their priority class.  A server can post a flattering
tokens/s while every foreground request blows its TTFT budget; the
inverse (shedding best-effort work to protect foreground SLOs) looks
like lost throughput but is exactly what the overload policy is paid
to do.  This module makes that distinction first-class:

- :class:`SLOTargets` — the per-priority-class contract: TTFT bound,
  per-token decode-latency bound (the ``decode_token_s`` derived
  metric of ``Request.timeline()``), both optional (``None`` = no
  latency bound; only healthy completion and deadline attainment
  count).
- :class:`SLOPolicy` — targets per priority class with a default for
  unlisted classes.
- :class:`SLOTracker` — fed every finished :class:`Request` by
  ``InferenceServer._finalize_finished``; classifies it met/missed
  against its class targets, accumulates goodput-vs-throughput token
  counters, keeps per-class attainment gauges in the shared
  :class:`MetricsRegistry` (``serving_slo_attainment{priority=...}``),
  and accounts **SLO debt** — the work the overload policy's
  shed/displace decisions gave up (requests shed per class, tokens of
  unearned budget) — so "how much did protecting the SLO cost" is a
  counter, not a guess.  Surfaced as ``stats()["slo"]``.

Classification rules (one request, against its class targets):

- a request is **attained** iff it finished healthy (``eos`` /
  ``length``) AND its TTFT and per-token decode latency are within
  any configured bounds;
- **deadline attainment** is tracked separately: a ``timeout`` finish
  is a deadline miss, everything else a hold;
- shed / rejected / breaker_open / draining requests are *not* SLO
  misses — they were refused, not served late — but shed work is
  charged to the debt counters.

Everything is host-side integer/float bookkeeping at request-finish
granularity; the step loop never touches it.
See ``docs/observability.md``, "SLO & goodput".
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

__all__ = ["SLOTargets", "SLOPolicy", "SLOTracker", "HEALTHY_REASONS"]

# pinned MIRRORS of :mod:`apex_tpu.serving.reasons` (the canonical
# finish-reason constants module).  Observability sits BELOW serving
# in the import graph — ``serving.api`` imports this package while it
# is still initializing — so a module-level import of serving here
# would cycle; ``tests/L0/test_reasons.py`` asserts these mirrors
# never drift from the canonical values.
HEALTHY_REASONS = frozenset({"eos", "length"})

# front-door refusals: never admitted (or given up at the door), so
# they are debt/refusal accounting, not latency-SLO misses.
# "handoff" is a disaggregated prefill replica's local terminal for a
# request whose decode moved to another replica (docs/serving.md,
# "Disaggregated prefill/decode") — served elsewhere, not served late
REFUSED_REASONS = frozenset({"rejected", "shed", "breaker_open",
                             "draining", "handoff"})

# mirror singletons used in classification below (same drift pin)
SHED = "shed"
TIMEOUT = "timeout"


@dataclasses.dataclass(frozen=True)
class SLOTargets:
    """Latency contract of one priority class.  ``None`` disables the
    corresponding bound (the request then only needs a healthy finish
    — and to hold its deadline — to count as attained).

    ``itl_p99_s`` bounds the request's inter-token-latency p99 — the
    per-TOKEN tail (``Request.timeline()``'s ``itl_p99_s``, from the
    wall gaps stamped as tokens are applied), vs ``decode_token_s``'s
    per-request average.  This is the bound head-of-line interference
    breaks first: one long prefill stalling the decode batch barely
    moves the average but punches straight through the gap tail — the
    headline metric of the disaggregated prefill/decode bench
    (``docs/serving.md``, "Disaggregated prefill/decode")."""

    ttft_s: Optional[float] = None
    decode_token_s: Optional[float] = None
    itl_p99_s: Optional[float] = None

    def __post_init__(self):
        for name in ("ttft_s", "decode_token_s", "itl_p99_s"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Targets per priority class; unlisted classes fall back to
    ``default``.  The stock default has no latency bounds — attainment
    then measures healthy completion and deadline holds, which is
    always meaningful; deployments pin real budgets per class."""

    targets: Dict[int, SLOTargets] = dataclasses.field(
        default_factory=dict)
    default: SLOTargets = dataclasses.field(default_factory=SLOTargets)

    def for_priority(self, priority: int) -> SLOTargets:
        return self.targets.get(priority, self.default)


class _ClassStats:
    """Per-priority-class tallies (plain ints — snapshot-friendly)."""

    __slots__ = ("requests", "attained", "ttft_met", "ttft_missed",
                 "decode_met", "decode_missed", "itl_met",
                 "itl_missed", "deadline_missed",
                 "shed_requests", "shed_tokens")

    def __init__(self):
        self.requests = 0           # served terminals (not refused)
        self.attained = 0
        self.ttft_met = 0
        self.ttft_missed = 0
        self.decode_met = 0
        self.decode_missed = 0
        self.itl_met = 0
        self.itl_missed = 0
        self.deadline_missed = 0
        self.shed_requests = 0
        self.shed_tokens = 0


class SLOTracker:
    """Accumulates SLO attainment, goodput, and shed debt.

    Args:
      policy: the :class:`SLOPolicy` to classify against.
      registry: optional :class:`MetricsRegistry`; when given,
        per-class attainment gauges
        (``serving_slo_attainment{priority=...}``) and the goodput /
        throughput counters live there too, so one Prometheus scrape
        carries the SLO surface.
    """

    def __init__(self, policy: Optional[SLOPolicy] = None,
                 registry=None):
        self.policy = policy if policy is not None else SLOPolicy()
        self._registry = registry
        self._classes: Dict[int, _ClassStats] = {}
        self.goodput_tokens = 0
        self.total_tokens = 0
        if registry is not None:
            self._goodput_c = registry.counter("serving_goodput_tokens")
            self._total_c = registry.counter("serving_served_tokens")
        else:
            self._goodput_c = self._total_c = None

    def _class(self, priority: int) -> _ClassStats:
        cs = self._classes.get(priority)
        if cs is None:
            cs = self._classes[priority] = _ClassStats()
        return cs

    # -- observation --------------------------------------------------------

    def observe(self, req) -> bool:
        """Classify one finished :class:`serving.scheduler.Request`;
        returns whether it attained its class SLO.  Refused requests
        (shed / rejected / breaker_open / draining) route to the debt
        side instead and return False."""
        if req.finish_reason in REFUSED_REASONS:
            if req.finish_reason == SHED:
                self.note_shed(req)
            return False
        cs = self._class(req.priority)
        cs.requests += 1
        tokens = len(req.generated)
        self.total_tokens += tokens
        if self._total_c is not None and tokens:
            self._total_c.incr(tokens)
        targets = self.policy.for_priority(req.priority)
        tl = req.timeline()
        met = req.finish_reason in HEALTHY_REASONS
        if req.finish_reason == TIMEOUT:
            cs.deadline_missed += 1
        if targets.ttft_s is not None and "ttft_s" in tl:
            if tl["ttft_s"] <= targets.ttft_s:
                cs.ttft_met += 1
            else:
                cs.ttft_missed += 1
                met = False
        if targets.decode_token_s is not None and "decode_token_s" in tl:
            if tl["decode_token_s"] <= targets.decode_token_s:
                cs.decode_met += 1
            else:
                cs.decode_missed += 1
                met = False
        if targets.itl_p99_s is not None and "itl_p99_s" in tl:
            if tl["itl_p99_s"] <= targets.itl_p99_s:
                cs.itl_met += 1
            else:
                cs.itl_missed += 1
                met = False
        if met:
            cs.attained += 1
            self.goodput_tokens += tokens
            if self._goodput_c is not None and tokens:
                self._goodput_c.incr(tokens)
        if self._registry is not None:
            self._registry.gauge(
                "serving_slo_attainment",
                priority=str(req.priority),
            ).update(cs.attained / cs.requests)
        return met

    def note_shed(self, req) -> int:
        """Charge one shed/displaced request to the debt counters;
        returns the token debt (the unearned remainder of its
        budget)."""
        debt = max(0, req.max_new_tokens - len(req.generated))
        cs = self._class(req.priority)
        cs.shed_requests += 1
        cs.shed_tokens += debt
        return debt

    # -- surface ------------------------------------------------------------

    @property
    def goodput_ratio(self) -> float:
        return (self.goodput_tokens / self.total_tokens
                if self.total_tokens else 0.0)

    def as_stats(self) -> dict:
        """The ``stats()["slo"]`` block: goodput vs throughput plus
        per-class attainment and debt (``docs/observability.md``)."""
        by_priority = {}
        for p in sorted(self._classes):
            cs = self._classes[p]
            t = self.policy.for_priority(p)
            by_priority[p] = {
                "requests": cs.requests,
                "attained": cs.attained,
                "attainment": round(cs.attained / cs.requests, 3)
                if cs.requests else 0.0,
                "ttft_target_s": t.ttft_s,
                "ttft_met": cs.ttft_met,
                "ttft_missed": cs.ttft_missed,
                "decode_token_target_s": t.decode_token_s,
                "decode_met": cs.decode_met,
                "decode_missed": cs.decode_missed,
                "itl_p99_target_s": t.itl_p99_s,
                "itl_met": cs.itl_met,
                "itl_missed": cs.itl_missed,
                "deadline_missed": cs.deadline_missed,
                "shed_requests": cs.shed_requests,
                "shed_tokens": cs.shed_tokens,
            }
        return {
            "goodput_tokens": self.goodput_tokens,
            "total_tokens": self.total_tokens,
            "goodput_ratio": round(self.goodput_ratio, 3),
            "by_priority": by_priority,
            "debt": {
                "shed_requests": sum(c.shed_requests
                                     for c in self._classes.values()),
                "shed_tokens": sum(c.shed_tokens
                                   for c in self._classes.values()),
            },
        }
