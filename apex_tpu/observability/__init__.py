"""apex_tpu.observability — unified telemetry for serving + training.

Two pieces, both process-wide and dependency-free:

- :mod:`observability.registry` — :class:`MetricsRegistry` of named,
  optionally-labeled :class:`Counter` / :class:`Gauge` /
  :class:`HistogramMeter` (log-bucketed, p50/p90/p99) metrics with
  snapshot/diff semantics, JSON-lines emission, and Prometheus
  text-format exposition.  The ``apex_tpu.utils`` meters become views
  onto a registry when constructed with ``registry=``.
- :mod:`observability.tracing` — :class:`SpanTracer`, a bounded
  ring-buffer span tracer exporting Chrome trace-event JSON
  (Perfetto-loadable).  Disabled by default (:data:`NULL_TRACER`,
  zero overhead); ``APEX_TPU_TRACE=/path.json`` or
  :func:`enable_tracing` turns it on.
- :mod:`observability.flightrecorder` — :class:`FlightRecorder`, a
  bounded ring of structured per-engine-step records (batch
  composition, admit/shed/preempt/evict decisions, memory occupancy,
  speculation outcomes, pressure, breaker state), disabled by default
  (:data:`NULL_FLIGHT_RECORDER`, zero allocations per step), plus
  :func:`write_postmortem` — the bundle (flight JSONL + metrics
  snapshot + Chrome trace + manifest) auto-dumped on chaos invariant
  violations, audit failures, and breaker-open transitions, rendered
  by ``tools/postmortem.py``.
- :mod:`observability.slo` — :class:`SLOTracker` over per-priority
  :class:`SLOTargets`: TTFT / per-token-decode / deadline attainment
  per class, goodput-vs-throughput token counters, and SLO-debt
  accounting for overload shed/displace decisions
  (``stats()["slo"]``).
- :mod:`observability.opsplane` — :class:`OpsServer`, the embedded
  loopback HTTP ops endpoint (``/healthz``, ``/metrics``,
  ``/statusz``, ``/debug/flight``, ``/debug/requests/<uid>``,
  ``POST /drain`` / ``/postmortem``); off by default
  (``ops_port=`` / ``APEX_TPU_OPS_PORT``), probed by
  ``tools/ops_probe.py``.
- :mod:`observability.watchdog` — :class:`HangWatchdog`, the serve
  loop's dead-man's switch: step-loop heartbeats, a no-progress
  deadline, thread-stack + postmortem capture on stall, and a 503
  ``/healthz`` flip; disabled by default at zero cost
  (:data:`NULL_WATCHDOG`).
- :mod:`observability.programs` — :class:`ProgramAccounting`,
  per-compiled-program call/wall/compile tallies behind the pinned
  ``stats()["programs"]`` table and the
  ``serving_program_*`` registry counters.

What is instrumented out of the box: the serving step loop (admit /
prefix-match / chunk-prefill / decode / evict / preempt spans,
per-request enqueue→admit→first-token→finish timelines feeding TTFT /
queue-wait / decode-latency histograms in
``InferenceServer.stats()``), engine compile events, checkpoint
save/restore/publish, and the amp train step (step time, loss-scale
trajectory, overflow skips).  See ``docs/observability.md``.
"""

from apex_tpu.observability.flightrecorder import (
    NULL_FLIGHT_RECORDER,
    POSTMORTEM_ENV,
    FlightRecorder,
    NullFlightRecorder,
    write_postmortem,
)
from apex_tpu.observability.journey import (
    JOURNEYS_ENV,
    NULL_JOURNEY_LOG,
    Journey,
    JourneyContext,
    JourneyLog,
    NullJourneyLog,
    dump_journeys,
    journeys_census,
    merge_exemplars,
    merge_journeys,
    resolve_journeys,
)
from apex_tpu.observability.opsplane import OPS_PORT_ENV, OpsServer
from apex_tpu.observability.programs import (
    NULL_PROGRAM_ACCOUNTING,
    NullProgramAccounting,
    ProgramAccounting,
)
from apex_tpu.observability.registry import (
    Counter,
    Gauge,
    HistogramMeter,
    MetricsRegistry,
    PROMETHEUS_CONTENT_TYPE,
    escape_label_value,
    fleet_prometheus_text,
    series_key,
    snapshot_diff,
)
from apex_tpu.observability.watchdog import (
    NULL_WATCHDOG,
    HangWatchdog,
    NullWatchdog,
)
from apex_tpu.observability.slo import SLOPolicy, SLOTargets, SLOTracker
from apex_tpu.observability.tracing import (
    NULL_TRACER,
    NullTracer,
    SpanTracer,
    TRACE_ENV,
    enable_tracing,
    get_tracer,
    set_tracer,
)

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "HangWatchdog",
    "HistogramMeter",
    "JOURNEYS_ENV",
    "Journey",
    "JourneyContext",
    "JourneyLog",
    "MetricsRegistry",
    "NULL_FLIGHT_RECORDER",
    "NULL_JOURNEY_LOG",
    "NULL_PROGRAM_ACCOUNTING",
    "NULL_TRACER",
    "NULL_WATCHDOG",
    "NullFlightRecorder",
    "NullJourneyLog",
    "NullProgramAccounting",
    "NullTracer",
    "NullWatchdog",
    "OPS_PORT_ENV",
    "OpsServer",
    "POSTMORTEM_ENV",
    "PROMETHEUS_CONTENT_TYPE",
    "ProgramAccounting",
    "SLOPolicy",
    "SLOTargets",
    "SLOTracker",
    "SpanTracer",
    "TRACE_ENV",
    "dump_journeys",
    "enable_tracing",
    "escape_label_value",
    "fleet_prometheus_text",
    "get_tracer",
    "journeys_census",
    "merge_exemplars",
    "merge_journeys",
    "resolve_journeys",
    "series_key",
    "set_tracer",
    "snapshot_diff",
    "write_postmortem",
]
