"""Per-compiled-program accounting for the serving engine.

The latency histograms (PR 4) split a step into *phases* (prefill /
chunk_prefill / decode / verify spans) and the pipeline block (PR 8)
into a host/device share — but none of them answer the question an
engine owner actually asks when a step gets slow: **which compiled
program is the time going to**, per shape variant?  A server runs a
small, closed set of XLA programs (one per prefill bucket, one per
chunk width, one decode, one per verify width, their fused-sampling
twins, and the COW block copy); this module tallies each of them.

- :class:`ProgramAccounting` — per-program-key cells of call count,
  host wall time, compile count, and compile time.  The key is the
  program name plus its shape variant (``prefill[64]``,
  ``chunk_prefill_sampled[32]``, ``decode``, ``verify[5]``,
  ``copy_blocks``), so a recompile storm or a mis-bucketed workload
  shows up as extra keys, not just extra time.  With a ``registry=``
  every cell also feeds labeled registry counters
  (``serving_program_calls{program=...}`` / ``_wall_s`` /
  ``_compiles`` / ``_compile_s``), so one Prometheus scrape carries
  the table.
- :data:`NULL_PROGRAM_ACCOUNTING` — the disabled instance
  (``enabled = False``); ``DecodeEngine`` guards its marks on
  ``programs.enabled or tracer.enabled`` so the disabled path skips
  even the clock reads.

Wall-time semantics: the tally measures the HOST-side cost of each
launch — argument staging plus the jit call.  For synchronously
executed programs (donated calls on CPU, materialized logits paths)
that includes device time; for the async-dispatched sampled twins the
device-bound share surfaces separately as the pipelined loop's retire
wait (``stats()["pipeline"]["host_stall_ms"]``).  A call whose jit
cache grew is a *compile call*: its whole wall time is attributed to
``compile_s`` (trace + lower + compile dominate it), and the
steady-state per-call figure excludes it — which is exactly why the
compile split exists: one slow first call must not poison the
steady-state average the table is read for.

Accounting never feeds back into scheduling and draws no randomness,
so a soak runs byte-identical with it on or off (the chaos axis runs
with it on).  Surfaced as the pinned ``stats()["programs"]`` table
and rendered over the wire by ``tools/ops_probe.py --programs``
(``docs/observability.md``, "Ops plane & watchdog").
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional


class NullProgramAccounting:
    """The disabled accounting: marks are no-ops and the engine skips
    clock reads entirely (``programs.enabled`` guard)."""

    enabled = False

    def begin(self) -> float:
        return 0.0

    def note(self, program: str, t0: float, compiled: bool) -> None:
        pass

    def table(self) -> Dict[str, Dict[str, Any]]:
        return {}


NULL_PROGRAM_ACCOUNTING = NullProgramAccounting()


class _Cell:
    """One program key's tallies (plus its registry counter views)."""

    __slots__ = ("calls", "wall_s", "compiles", "compile_s",
                 "_c_calls", "_c_wall", "_c_compiles", "_c_compile_s")

    def __init__(self, registry, program: str):
        self.calls = 0
        self.wall_s = 0.0
        self.compiles = 0
        self.compile_s = 0.0
        if registry is not None:
            self._c_calls = registry.counter(
                "serving_program_calls", program=program)
            self._c_wall = registry.counter(
                "serving_program_wall_s", program=program)
            self._c_compiles = registry.counter(
                "serving_program_compiles", program=program)
            self._c_compile_s = registry.counter(
                "serving_program_compile_s", program=program)
        else:
            self._c_calls = self._c_wall = None
            self._c_compiles = self._c_compile_s = None

    def note(self, wall: float, compiled: bool) -> None:
        self.calls += 1
        self.wall_s += wall
        if compiled:
            self.compiles += 1
            self.compile_s += wall
        if self._c_calls is not None:
            self._c_calls.incr()
            self._c_wall.incr(wall)
            if compiled:
                self._c_compiles.incr()
                self._c_compile_s.incr(wall)


class ProgramAccounting:
    """Call-count + wall-time + compile tallies per compiled program.

    Args:
      registry: optional :class:`MetricsRegistry`; each program key
        then feeds four labeled counters so scrapes carry the table.
      clock: injectable monotonic-seconds source (deterministic
        tests).
    """

    enabled = True

    def __init__(self, registry=None, clock=time.perf_counter):
        self._registry = registry
        self._clock = clock
        self._cells: Dict[str, _Cell] = {}

    def begin(self) -> float:
        """Pre-launch clock mark; pair with :meth:`note`."""
        return self._clock()

    def note(self, program: str, t0: float, compiled: bool) -> None:
        """Account one launch of ``program`` started at ``t0``;
        ``compiled`` attributes the call's wall time to compilation."""
        wall = self._clock() - t0
        cell = self._cells.get(program)
        if cell is None:
            cell = self._cells[program] = _Cell(self._registry, program)
        cell.note(wall, compiled)

    def table(self) -> Dict[str, Dict[str, Any]]:
        """``{program_key: row}`` sorted by key — the
        ``stats()["programs"]["by_program"]`` table.  ``steady_ms``
        is the per-call average EXCLUDING compile calls (0.0 until a
        program has run post-compile)."""
        out: Dict[str, Dict[str, Any]] = {}
        for key in sorted(self._cells):
            c = self._cells[key]
            steady_calls = c.calls - c.compiles
            steady_s = c.wall_s - c.compile_s
            out[key] = {
                "calls": c.calls,
                "compiles": c.compiles,
                "wall_ms": round(c.wall_s * 1e3, 3),
                "compile_ms": round(c.compile_s * 1e3, 3),
                "steady_ms": round(steady_s / steady_calls * 1e3, 4)
                if steady_calls > 0 else 0.0,
            }
        return out
