"""Embedded HTTP ops plane for a live :class:`InferenceServer`.

Everything the observability stack accumulated so far —
``stats()``, ``prometheus_text()``, the flight ring, per-request
timelines, postmortem bundles — was reachable only by code already
holding the server object.  The ops plane puts those signals on the
wire: a dependency-free stdlib ``http.server`` on a daemon thread,
loopback-bound, OFF by default (``ops_port=`` or
``APEX_TPU_OPS_PORT``; port 0 binds an ephemeral port, readable back
from :attr:`OpsServer.port`).  This is what the ROADMAP's
multi-replica front door scrapes to load-balance and fail over — and
what an operator curls at 3am.

Endpoints:

- ``GET /healthz`` — liveness/readiness in one probe: 200
  ``{"status": "ok"}`` on a healthy server, 503 with ``"draining"``
  / ``"breaker_open"`` / ``"stalled"`` (watchdog) / ``"closed"``
  otherwise, so a router can pull the replica on status code alone.
  Deliberately **lock-free** (plain attribute reads): the one moment
  health must answer is while the serve loop is wedged holding the
  ops lock.
- ``GET /metrics`` — ``MetricsRegistry.prometheus_text()`` under the
  proper ``text/plain; version=0.0.4`` content type (scrapers key on
  it).  Also lock-free: a scrape must not block behind a slow step.
- ``GET /statusz`` — the full ``stats()`` JSON (programs table,
  watchdog, SLO, memory, ...), serialized against the step loop.
- ``GET /debug/flight?n=N`` — the flight-recorder tail as JSONL
  (empty with the null recorder).
- ``GET /debug/requests/<uid>`` — one request's ``timeline()`` (the
  slice ``tools/postmortem.py --request`` renders from bundles, but
  live) plus its current state; 404 for unknown uids.
- ``GET /debug/journey/<rid>`` — one request's merged cross-replica
  journey (``docs/observability.md``, "Request journeys &
  exemplars"); 409 when journeys are disabled, 404 for unknown rids.
- ``GET /metrics/fleet`` — fleet-wide Prometheus exposition with a
  ``replica=<name>`` label per replica series (fleet ops plane only;
  404 on a single server's).
- ``POST /drain`` / ``POST /postmortem`` — authenticated-by-loopback
  triggers into :meth:`InferenceServer.drain` /
  :meth:`~InferenceServer.dump_postmortem` (non-loopback peers get
  403; the listener is loopback-bound anyway — defense in depth).
- ``POST /generate`` + ``GET /stream/<id>`` — the streaming front
  door (``docs/serving.md``, "Streaming & cancellation"): the POST
  submits ``{"prompt": [...], "max_new_tokens": N, ...}`` and
  returns the stream id; the GET serves that request's tokens as
  Server-Sent Events (``event: token`` per retired token, one
  ``event: end`` carrying the ``finish_reason``).  The SSE loop
  blocks on the stream broker's OWN lock — never the ops lock — and
  a broken client socket **cancels** the request
  (``finish_reason="cancelled"``), freeing its blocks mid-decode.
  Hosted by both a single server's ops plane and the fleet's
  aggregate one (``RouterFleet(ops_port=)`` — streams there survive
  failover and hand-off).

Mutating reads (``/statusz``, ``/debug/*``) and the POST triggers
serialize against the serve loop through :attr:`OpsServer.lock` —
``InferenceServer.step()`` holds it per iteration *only while an ops
plane is attached*, so servers without one pay nothing.  Request
handling is bounded: loopback bind, per-connection socket timeout,
a request-body cap, and one-shot HTTP/1.0 connections.

``tools/ops_probe.py`` is the CLI client (poll, ``--assert-healthy``
gate, program-table rendering).  See ``docs/observability.md``,
"Ops plane & watchdog".
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from apex_tpu.observability.registry import PROMETHEUS_CONTENT_TYPE

OPS_PORT_ENV = "APEX_TPU_OPS_PORT"

_LOOPBACK = ("127.0.0.1", "::1", "::ffff:127.0.0.1")

# one request body bound — the POST triggers carry no payload, so
# anything large is abuse, not traffic
_MAX_BODY = 64 * 1024


class _Handler(BaseHTTPRequestHandler):
    """Routes every request through the owning :class:`OpsServer`."""

    timeout = 10.0            # per-connection socket budget (bounded)

    def do_GET(self):         # noqa: N802 — http.server API
        self.server.ops._handle(self, "GET")

    def do_POST(self):        # noqa: N802
        self.server.ops._handle(self, "POST")

    def log_message(self, fmt, *args):
        pass                  # counted in the registry, not stderr


class OpsServer:
    """The embedded ops endpoint for one ``InferenceServer``.

    Args:
      server: the (duck-typed) ``InferenceServer`` to expose.
      port: TCP port on loopback; 0 binds an ephemeral port
        (:attr:`port` holds the real one).
      host: bind address — loopback by default and by intent.
      clock: injectable seconds source for ``/healthz`` uptime
        (default: the serving server's own clock).
      counters: optional ``CounterMeter`` (label ``endpoint``)
        counting handled requests into the shared registry.
    """

    def __init__(self, server, *, port: int = 0,
                 host: str = "127.0.0.1", clock=None, counters=None):
        self.server = server
        self.lock = threading.RLock()
        self.counters = counters
        self._clock = clock if clock is not None else server.clock
        self._started_at = self._clock()
        # SSE heartbeat cadence: bounds both disconnect detection and
        # how long a stream handler can block between wakeups
        self._sse_ping_s = 10.0
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "OpsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.2},
                name="apex-tpu-ops", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5.0)
        self._httpd.server_close()

    # -- routing -----------------------------------------------------------

    def _handle(self, h: BaseHTTPRequestHandler, method: str) -> None:
        url = urlparse(h.path)
        path, query = url.path.rstrip("/") or "/", parse_qs(url.query)
        try:
            if method == "GET":
                if path == "/healthz":
                    return self._count_send(h, "healthz",
                                            *self._healthz())
                if path == "/metrics":
                    # apexlint: disable=lock-discipline — documented lock-free: the registry serializes internally and a scrape must not block behind a wedged step
                    text = self.server.registry.prometheus_text()
                    return self._count_send(
                        h, "metrics", 200, text.encode(),
                        PROMETHEUS_CONTENT_TYPE)
                if path == "/metrics/fleet":
                    return self._metrics_fleet(h)
                if path == "/statusz":
                    with self.lock:
                        stats = self.server.stats()
                    return self._count_send(h, "statusz",
                                            *_json(200, stats))
                if path == "/debug/flight":
                    return self._count_send(h, "debug_flight",
                                            *self._flight(query))
                if path.startswith("/debug/requests/"):
                    return self._count_send(
                        h, "debug_requests",
                        *self._request(path.rsplit("/", 1)[1]))
                if path.startswith("/debug/journey/"):
                    return self._count_send(
                        h, "debug_journey",
                        *self._journey(path.rsplit("/", 1)[1]))
                if path.startswith("/stream/"):
                    return self._stream(h, path.rsplit("/", 1)[1])
            elif method == "POST":
                if h.client_address[0] not in _LOOPBACK:
                    return self._count_send(h, "forbidden", *_json(
                        403, {"error": "loopback only"}))
                body = self._read_body(h)
                if body is None:
                    return self._count_send(h, "too_large", *_json(
                        413, {"error": "request body too large"}))
                if path == "/drain":
                    return self._count_send(h, "drain",
                                            *self._drain())
                if path == "/postmortem":
                    return self._count_send(h, "postmortem",
                                            *self._postmortem())
                if path == "/generate":
                    return self._count_send(h, "generate",
                                            *self._generate(body))
            self._count_send(h, "unknown", *_json(
                404, {"error": f"no such endpoint: {method} {path}"}))
        except (BrokenPipeError, ConnectionResetError):
            pass              # client went away mid-reply; nothing owed
        except Exception as e:  # noqa: BLE001 — a handler bug must
            #                     not kill the ops thread pool
            try:
                self._count_send(h, "error",
                                 *_json(500, {"error": repr(e)}))
            except OSError:
                pass

    def _count_send(self, h, endpoint: str, code: int, body: bytes,
                    content_type: str) -> None:
        if self.counters is not None:
            self.counters.incr(endpoint)
        h.send_response(code)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    @staticmethod
    def _read_body(h) -> Optional[bytes]:
        """Bounded body read; None = over the cap (413)."""
        n = int(h.headers.get("Content-Length") or 0)
        if n > _MAX_BODY:
            return None
        return h.rfile.read(n) if n else b""

    # -- endpoint bodies ---------------------------------------------------

    # apexlint: disable=lock-discipline — documented lock-free contract: health MUST answer while the serve loop is wedged holding the ops lock
    def _healthz(self) -> Tuple[int, bytes, str]:
        """Lock-free health: readable even while the serve loop is
        wedged inside a step holding the ops lock."""
        srv = self.server
        if srv.watchdog.stalled:
            status = "stalled"
        elif srv.closed:
            status = "closed"
        elif srv.draining:
            status = "draining"
        elif srv.breaker is not None and srv.breaker.state == "open":
            status = "breaker_open"
        else:
            status = "ok"
        sched = srv.scheduler
        body = {
            "status": status,
            "iter": srv._iter,
            "breaker": (srv.breaker.state if srv.breaker is not None
                        else "disabled"),
            "pressure": round(srv.pressure_gauge.val, 4),
            # the router-scrape trio (docs/serving.md, "Multi-replica
            # routing"): one cheap machine-readable probe carries the
            # placement signal (pressure), the lifecycle flag
            # (draining), and the occupancy (waiting + running) a
            # balancer keys on — no /statusz parse needed.  Plain
            # attribute reads, same lock-free contract as the rest of
            # this body.
            "draining": bool(srv.draining),
            "live_requests": len(sched.waiting) + len(sched.running),
            "watchdog_stalls": srv.watchdog.stalls,
            "uptime_s": round(self._clock() - self._started_at, 3),
        }
        # streaming gauges ride the same probe (broker-locked, not
        # ops-locked — still safe while the serve loop is wedged)
        broker = getattr(srv, "stream_broker", None)
        body["active_streams"] = (broker.active
                                  if broker is not None else 0)
        body["stream_backpressure_drops"] = (
            broker.backpressure_drops if broker is not None else 0)
        return _json(200 if status == "ok" else 503, body)

    def _flight(self, query) -> Tuple[int, bytes, str]:
        try:
            n = int(query.get("n", ["50"])[0])
        except ValueError:
            return _json(400, {"error": "n must be an integer"})
        with self.lock:
            records = self.server.recorder.records()
        tail = records[-n:] if n > 0 else ()
        body = "".join(json.dumps(r, sort_keys=True) + "\n"
                       for r in tail)
        return 200, body.encode(), "application/jsonl; charset=utf-8"

    def _request(self, uid_text: str) -> Tuple[int, bytes, str]:
        try:
            uid = int(uid_text)
        except ValueError:
            return _json(400, {"error": f"bad uid: {uid_text!r}"})
        with self.lock:
            sched = self.server.scheduler
            req, state = None, None
            for r in sched.finished:
                if r.uid == uid:
                    req, state = r, "finished"
                    break
            if req is None:
                r = sched.running.get(uid)
                if r is not None:
                    req, state = r, "running"
            if req is None:
                for r in sched.waiting:
                    if r.uid == uid:
                        req, state = r, "waiting"
                        break
            if req is None:
                return _json(404, {"error": f"unknown request {uid}"})
            body = {"state": state, "timeline": req.timeline()}
        return _json(200, body)

    def _metrics_fleet(self, h) -> None:
        """Fleet-wide exposition (``fleet_metrics_text``): every
        replica's series under a ``replica=<name>`` label in one
        conformant page.  404 on a single server's ops plane — the
        plain ``/metrics`` already is the whole story there."""
        fm = getattr(self.server, "fleet_metrics_text", None)
        if fm is None:
            return self._count_send(h, "metrics_fleet", *_json(
                404, {"error": "not a fleet ops plane"}))
        # apexlint: disable=lock-discipline — documented lock-free: same scrape contract as /metrics (the registries serialize internally)
        text = fm()
        return self._count_send(h, "metrics_fleet", 200,
                                text.encode(),
                                PROMETHEUS_CONTENT_TYPE)

    def _journey(self, rid_text: str) -> Tuple[int, bytes, str]:
        """One request's merged journey (``docs/observability.md``,
        "Request journeys & exemplars"): the fleet ops plane merges
        hops across every replica the rid touched; a single server's
        serves its local log.  409 when the correlation plane is not
        armed — distinct from 404 (armed, rid unknown), so a prober
        can tell "turn it on" from "no such request"."""
        try:
            rid = int(rid_text)
        except ValueError:
            return _json(400, {"error": f"bad rid: {rid_text!r}"})
        jlog = getattr(self.server, "journeys", None)
        if jlog is None or not jlog.enabled:
            return _json(409, {"error": "journeys disabled "
                                        "(enable_journeys=False)"})
        with self.lock:
            j = self.server.journey(rid)
        if j is None:
            return _json(404, {"error": f"unknown journey rid {rid}"})
        return _json(200, j)

    def _drain(self) -> Tuple[int, bytes, str]:
        with self.lock:
            stats = self.server.drain()
        return _json(200, {
            "status": "drained",
            "requests_finished": stats["requests_finished"]})

    # -- streaming front door (docs/serving.md) ----------------------------

    def _generate(self, body: bytes) -> Tuple[int, bytes, str]:
        """Submit one request from a JSON body; returns the id to
        ``GET /stream/<id>`` (the router-level ``rid`` on a fleet ops
        plane, the request ``uid`` on a single server's)."""
        try:
            payload = json.loads(body or b"{}")
            prompt = [int(t) for t in payload["prompt"]]
            max_new = int(payload["max_new_tokens"])
        except (ValueError, TypeError, KeyError) as e:
            return _json(400, {"error": f"bad generate body: {e!r}"})
        eos_id = payload.get("eos_id")
        priority = int(payload.get("priority", 0))
        srv = self.server
        if getattr(srv, "stream_broker", None) is None:
            return _json(409, {"error": "streaming disabled "
                                        "(enable_streaming=False)"})
        try:
            # apexlint: disable=lock-discipline — documented lock-free: submit() takes the ops lock itself (both server kinds); taking self.lock here would deadlock a non-reentrant configuration and serialize admission behind slow scrapes
            req = srv.submit(prompt, max_new,
                             eos_id if eos_id is None else int(eos_id),
                             priority=priority)
        except (ValueError, TypeError, RuntimeError) as e:
            return _json(400, {"error": str(e)})
        sid = getattr(req, "rid", None)
        if sid is None:
            sid = req.uid
        out = {"id": sid, "finished": bool(req.finished)}
        if req.finished:       # turned away at the front door
            out["finish_reason"] = req.finish_reason
        return _json(200, out)

    def _stream(self, h, id_text: str) -> None:
        """Serve one request's tokens as SSE.  The setup (stream
        lookup) serializes on the ops lock; the delivery loop blocks
        only on the broker's own condition variable, so a slow or
        stalled consumer thread never holds the ops lock.  A broken
        client socket cancels the request — the disconnect-
        cancellation contract the chaos soak fires faults at."""
        try:
            sid = int(id_text)
        except ValueError:
            return self._count_send(h, "stream", *_json(
                400, {"error": f"bad stream id: {id_text!r}"}))
        srv = self.server
        if getattr(srv, "stream_broker", None) is None:
            return self._count_send(h, "stream", *_json(
                409, {"error": "streaming disabled"}))
        try:
            # apexlint: disable=lock-discipline — documented lock-free: stream() takes the ops lock itself; the delivery loop below must NOT hold self.lock (it blocks on the broker condition for seconds at a time)
            stream = srv.stream(sid)
        except KeyError:
            return self._count_send(h, "stream", *_json(
                404, {"error": f"unknown stream id {sid}"}))
        if self.counters is not None:
            self.counters.incr("stream")
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.end_headers()
        try:
            while True:
                toks = stream.take(timeout=self._sse_ping_s)
                for tok in toks:
                    h.wfile.write(
                        f"event: token\ndata: {tok}\n\n".encode())
                if stream.done:
                    h.wfile.write(
                        f"event: end\ndata: "
                        f"{stream.finish_reason}\n\n".encode())
                    h.wfile.flush()
                    return
                if not toks:
                    # heartbeat comment: the only way a one-way SSE
                    # pipe learns the client hung up between tokens
                    h.wfile.write(b": ping\n\n")
                h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client disconnected mid-stream: free its blocks NOW
            stream.close()
            # apexlint: disable=lock-discipline — documented lock-free: cancel() takes the ops lock itself; holding self.lock across it would nest the locks in the opposite order of /statusz
            srv.cancel(sid)

    def _postmortem(self) -> Tuple[int, bytes, str]:
        """Bundle-path choice AND the dump run under one lock hold:
        picking the name from an unlocked ``_iter`` read raced the
        step loop (apexlint lock-discipline) and left a TOCTOU
        between the exists() scan and the write."""
        srv = self.server
        with self.lock:
            base = srv._postmortem_dir or tempfile.gettempdir()
            path = os.path.join(base,
                                f"ops_postmortem_iter{srv._iter}")
            i = 1
            while os.path.exists(path):
                path = os.path.join(
                    base, f"ops_postmortem_iter{srv._iter}_{i}")
                i += 1
            manifest = srv.dump_postmortem(path, reason="ops_request")
        return _json(200, {"path": path, "manifest": manifest})


def _json(code: int, payload) -> Tuple[int, bytes, str]:
    body = json.dumps(payload, sort_keys=True, default=str).encode()
    return code, body, "application/json; charset=utf-8"
